"""Probe 2: the fused Newton step with ENTITIES IN LANES.

Layout: the slab is pre-transposed once per dataset to [S, R, B] (leading-dim
indexing of 3-D VMEM refs is contiguous; middle-dim slices are strided
copies); each
grid step owns 128 entities (the lane width). Every operation is then an
elementwise or single-axis reduce over [sublane, 128] tiles — no
per-entity dots, no serialization: H lives in a [S*S, 128] VMEM scratch
and never touches HBM.

Compare against the batch-minor XLA step at bench-user shapes.
"""

import functools
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

B, R, S = 99_976, 64, 17
BL = 128  # entities per grid step == lane width
T = 16


def _sigmoid(z):
    return 1.0 / (1.0 + jnp.exp(-z))


def _logistic_loss(z, y):
    return jnp.log1p(jnp.exp(-jnp.abs(z))) + jnp.maximum(z, 0.0) - z * y


def kernel(x_ref, w_ref, y_ref, wt_ref, off_ref, l2_ref, mt_ref, vm_ref,
           f_ref, w_out, f_out, g_out, imp_out, h_ref):
    # x_ref [S, R, BL]; vectors [S, BL]; rows [R, BL]; scalars [1, BL].
    w = w_ref[...]
    l2 = l2_ref[...]
    mt = mt_ref[...]
    vm = vm_ref[...]
    y = y_ref[...]
    wt = wt_ref[...]
    off = off_ref[...]
    f_prev = f_ref[...]          # [1, BL]

    # z[r, :] = sum_s x[r, s, :] * w[s, :]
    z = off
    for s in range(S):
        z = z + x_ref[s] * w[s:s + 1, :]
    p = _sigmoid(z)
    c = wt * p * (1 - p)
    d1 = wt * (p - y)

    # H build into 3-D scratch [S, S, BL]; g as [S, BL].
    g_rows = []
    for s in range(S):
        xs = x_ref[s]
        xc = xs * c
        for t in range(s + 1):
            row = jnp.sum(xc * x_ref[t], axis=0, keepdims=True)
            if t == s:
                row = row + l2[s:s + 1, :] + (1.0 - vm[s:s + 1, :])
            h_ref[s, t, :] = row[0]
            if t != s:
                h_ref[t, s, :] = row[0]
        g_rows.append(jnp.sum(xs * d1, axis=0, keepdims=True))
    g = jnp.concatenate(g_rows, axis=0) + l2 * (w - mt)
    g = g * vm

    # CG: S steps. The matvec is S broadcast-FMAs over [S, BL] tiles
    # (H[:, t, :] * p[t]), NOT S*S scalar-row ops — [1, BL] rows use 1/8
    # of the VPU and dominated the first version of this kernel.
    def matvec(pp):
        acc = h_ref[:, 0, :] * pp[0:1, :]
        for t in range(1, S):
            acc = acc + h_ref[:, t, :] * pp[t:t + 1, :]
        return acc

    b0 = -g

    def cg_step(_, st):
        xx, rr, pp, rs = st
        hp = matvec(pp)
        denom = jnp.sum(pp * hp, axis=0, keepdims=True)
        alpha = rs / jnp.maximum(denom, 1e-30)
        xx = xx + alpha * pp
        rr = rr - alpha * hp
        rs2 = jnp.sum(rr * rr, axis=0, keepdims=True)
        pp = rr + (rs2 / jnp.maximum(rs, 1e-30)) * pp
        return xx, rr, pp, rs2

    d, _, _, _ = lax.fori_loop(
        0, S, cg_step,
        (jnp.zeros_like(b0), b0, b0,
         jnp.sum(b0 * b0, axis=0, keepdims=True)),
    )
    d = d * vm
    gd = jnp.sum(g * d, axis=0, keepdims=True)  # [1, BL]
    bad = gd >= 0.0
    d = jnp.where(bad, -g, d)
    gd = jnp.where(bad, -jnp.sum(g * g, axis=0, keepdims=True), gd)

    zd = jnp.zeros_like(z)
    for s in range(S):
        zd = zd + x_ref[s] * d[s:s + 1, :]

    # Line search: T sequential trials, each [R, BL] work; track the
    # best (largest) passing step per lane.
    t_sel = jnp.zeros_like(gd)
    f_sel = f_prev
    for k in range(T):
        tk = 0.5 ** k
        f_k = jnp.sum(wt * _logistic_loss(z + tk * zd, y), axis=0,
                      keepdims=True)
        f_k = f_k + 0.5 * jnp.sum(
            l2 * (w + tk * d - mt) ** 2, axis=0, keepdims=True)
        ok = (f_k <= f_prev + 1e-4 * tk * gd) & (t_sel == 0.0)
        t_sel = jnp.where(ok, tk, t_sel)
        f_sel = jnp.where(ok, f_k, f_sel)
    improved = (t_sel > 0.0) & (f_sel < f_prev)
    w_new = jnp.where(improved, w + t_sel * d, w)

    # Fresh objective + gradient at w_new (slab still in VMEM).
    z2 = off
    for s in range(S):
        z2 = z2 + x_ref[s] * w_new[s:s + 1, :]
    f_new = jnp.sum(wt * _logistic_loss(z2, y), axis=0, keepdims=True) \
        + 0.5 * jnp.sum(l2 * (w_new - mt) ** 2, axis=0, keepdims=True)
    d2 = wt * (_sigmoid(z2) - y)
    g2_rows = []
    for s in range(S):
        g2_rows.append(jnp.sum(x_ref[s] * d2, axis=0, keepdims=True))
    g_new = (jnp.concatenate(g2_rows, axis=0) + l2 * (w_new - mt)) * vm

    w_out[...] = w_new
    f_out[...] = f_new
    g_out[...] = g_new
    imp_out[...] = improved.astype(jnp.float32)


@jax.jit
def pallas_step(x_t, w_t, y_t, wt_t, off_t, l2_t, mt_t, vm_t, f_t):
    bpad = x_t.shape[-1]
    nb = bpad // BL
    vec = lambda: pl.BlockSpec((S, BL), lambda i: (0, i))  # noqa: E731
    row = lambda: pl.BlockSpec((R, BL), lambda i: (0, i))  # noqa: E731
    one = lambda: pl.BlockSpec((1, BL), lambda i: (0, i))  # noqa: E731
    return pl.pallas_call(
        kernel,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((S, R, BL), lambda i: (0, 0, i)),
            vec(), row(), row(), row(), vec(), vec(), vec(), one(),
        ],
        out_specs=[vec(), one(), vec(), one()],
        out_shape=[
            jax.ShapeDtypeStruct((S, bpad), jnp.float32),
            jax.ShapeDtypeStruct((1, bpad), jnp.float32),
            jax.ShapeDtypeStruct((S, bpad), jnp.float32),
            jax.ShapeDtypeStruct((1, bpad), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((S, S, BL), jnp.float32)],
    )(x_t, w_t, y_t, wt_t, off_t, l2_t, mt_t, vm_t, f_t)


@functools.partial(jax.jit, static_argnames=())
def xla_step(x, w, y, wt, off, l2, mt, vm, f):
    """Batch-minor XLA step (entity-major [B, ...] inputs)."""
    f = f[:, 0]
    z = jnp.einsum("brs,bs->br", x, w) + off
    p = jax.nn.sigmoid(z)
    c = wt * p * (1 - p)
    h = jnp.einsum("brs,brt->bst", x * c[:, :, None], x)
    h = h + (l2 + (1.0 - vm))[:, :, None] * jnp.eye(S)[None]
    g = (jnp.einsum("brs,br->bs", x, wt * (p - y)) + l2 * (w - mt)) * vm
    h_sb = jnp.transpose(h, (1, 2, 0))

    def cg_step(_, st):
        xx, rr, pp, rs = st
        hp = jnp.sum(h_sb * pp[None, :, :], axis=1)
        denom = jnp.sum(pp * hp, axis=0)
        alpha = rs / jnp.maximum(denom, 1e-30)
        xx = xx + alpha[None] * pp
        rr = rr - alpha[None] * hp
        rs2 = jnp.sum(rr * rr, axis=0)
        pp = rr + (rs2 / jnp.maximum(rs, 1e-30))[None] * pp
        return xx, rr, pp, rs2

    b0 = -jnp.transpose(g)
    d0, _, _, _ = lax.fori_loop(
        0, S, cg_step,
        (jnp.zeros_like(b0), b0, b0, jnp.sum(b0 * b0, axis=0)))
    d = jnp.transpose(d0) * vm
    gd = jnp.sum(g * d, axis=-1)
    bad = gd >= 0.0
    d = jnp.where(bad[:, None], -g, d)
    gd = jnp.where(bad, -jnp.sum(g * g, axis=-1), gd)
    zd = jnp.einsum("brs,bs->br", x, d)
    ts = 0.5 ** jnp.arange(T, dtype=jnp.float32)
    z_t = z[None] + ts[:, None, None] * zd[None]
    loss_t = jnp.logaddexp(0.0, z_t) - z_t * y[None]
    w_t = w[None] + ts[:, None, None] * d[None]
    f_t = jnp.sum(wt[None] * loss_t, axis=-1) + 0.5 * jnp.sum(
        l2[None] * (w_t - mt[None]) ** 2, axis=-1)
    armijo = f_t <= f[None] + 1e-4 * ts[:, None] * gd[None]
    first = jnp.argmax(armijo, axis=0)
    any_ok = jnp.any(armijo, axis=0)
    t_sel = ts[first]
    f_sel = jnp.take_along_axis(f_t, first[None], axis=0)[0]
    improved = any_ok & (f_sel < f)
    w_new = jnp.where(improved[:, None], w + t_sel[:, None] * d, w)
    z2 = jnp.einsum("brs,bs->br", x, w_new) + off
    f_new = jnp.sum(wt * (jnp.logaddexp(0.0, z2) - z2 * y), axis=-1) \
        + 0.5 * jnp.sum(l2 * (w_new - mt) ** 2, axis=-1)
    p2 = jax.nn.sigmoid(z2)
    g_new = (jnp.einsum("brs,br->bs", x, wt * (p2 - y))
             + l2 * (w_new - mt)) * vm
    return w_new, f_new[:, None], g_new, improved.astype(jnp.float32)[:, None]


def main():
    rng = np.random.default_rng(0)
    bpad = (B // BL) * BL
    x = rng.normal(size=(bpad, R, S)).astype(np.float32)
    w = rng.normal(size=(bpad, S)).astype(np.float32) * 0.1
    y = (rng.random((bpad, R)) > 0.5).astype(np.float32)
    wt = rng.random((bpad, R)).astype(np.float32)
    off = np.zeros((bpad, R), np.float32)
    l2 = np.ones((bpad, S), np.float32)
    mt = np.zeros((bpad, S), np.float32)
    vm = np.ones((bpad, S), np.float32)
    z = np.einsum("brs,bs->br", x, w)
    f0 = (wt * (np.logaddexp(0.0, z) - z * y)).sum(-1) \
        + 0.5 * (l2 * w ** 2).sum(-1)

    # Entity-major operands for XLA.
    args_b = tuple(jnp.asarray(a) for a in
                   (x, w, y, wt, off, l2, mt, vm, f0[:, None]))
    # Lane-major operands for pallas.
    args_l = (
        jnp.asarray(np.transpose(x, (2, 1, 0))),
        jnp.asarray(w.T), jnp.asarray(y.T), jnp.asarray(wt.T),
        jnp.asarray(off.T), jnp.asarray(l2.T), jnp.asarray(mt.T),
        jnp.asarray(vm.T), jnp.asarray(f0[None, :]),
    )

    t0 = time.perf_counter()
    outs_p = pallas_step(*args_l)
    print(f"pallas compile+run: {time.perf_counter() - t0:.1f}s",
          flush=True)
    outs_x = xla_step(*args_b)
    pairs = (
        (outs_p[0].T, outs_x[0], "w"),
        (outs_p[1].T, outs_x[1], "f"),
        (outs_p[2].T, outs_x[2], "g"),
        (outs_p[3].T, outs_x[3], "imp"),
    )
    for a, b, name in pairs:
        err = float(jnp.max(jnp.abs(a - b)))
        rel = err / (float(jnp.max(jnp.abs(b))) + 1e-30)
        print(f"parity {name}: max abs {err:.3e} rel {rel:.3e}",
              flush=True)

    # Chain K steps inside ONE jit per timing sample: a single dispatch +
    # pull costs ~100ms of tunnel round trip, which at ~100ms/step would
    # swamp the thing being measured.
    K = 20

    def chain2(step):
        @jax.jit
        def run(a):
            x, w, y, wt, off, l2, mt, vm, f = a

            def body(_, st):
                w_, f_ = st
                outs = step(x, w_, y, wt, off, l2, mt, vm, f_)
                return outs[0], outs[1]

            w_fin, f_fin = lax.fori_loop(0, K, body, (w, f))
            return f_fin

        return run

    for name, step, args in (("pallas", pallas_step, args_l),
                             ("xla", xla_step, args_b)):
        run = chain2(step)
        float(np.asarray(run(args)).sum())
        t0 = time.perf_counter()
        for _ in range(3):
            float(np.asarray(run(args)).sum())
        per = (time.perf_counter() - t0) / 3 / K * 1000
        print(f"{name}: {per:.1f} ms per Newton step "
              f"(K={K} chained)", flush=True)


if __name__ == "__main__":
    main()
