"""Profile one fused bench fit and print top TPU ops by total time.

Hand-rolled xplane.pb parse (no tensorboard plugin in the image).
Usage: python experiments/trace_top_ops.py [linear|logistic]
"""

import collections
import glob
import os
import shutil
import sys
import tempfile

sys.path.insert(0, "/root/repo")


def parse_msg(buf, handlers):
    from google.protobuf.internal import decoder

    pos, end = 0, len(buf)
    while pos < end:
        tag, pos = decoder._DecodeVarint(buf, pos)
        field, wt = tag >> 3, tag & 7
        if wt == 2:
            ln, pos = decoder._DecodeVarint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wt == 0:
            val, pos = decoder._DecodeVarint(buf, pos)
        elif wt == 5:
            val = buf[pos:pos + 4]
            pos += 4
        elif wt == 1:
            val = buf[pos:pos + 8]
            pos += 8
        else:
            raise ValueError(f"wire type {wt}")
        h = handlers.get(field)
        if h:
            h(val)


def top_ops(xplane_path, top=30):
    data = open(xplane_path, "rb").read()
    planes = []
    parse_msg(data, {1: planes.append})
    for plane in planes:
        name = [None]
        lines = []
        emeta = {}

        def on_emeta(v):
            key = [None]
            val = [None]
            parse_msg(v, {1: lambda x: key.__setitem__(0, x),
                          2: lambda x: val.__setitem__(0, x)})
            nm = [None]
            dn = [None]
            if val[0] is not None:
                parse_msg(val[0], {
                    2: lambda x: nm.__setitem__(
                        0, x.decode() if isinstance(x, bytes) else None),
                    4: lambda x: dn.__setitem__(
                        0, x.decode() if isinstance(x, bytes) else None),
                })
            emeta[key[0]] = dn[0] or nm[0]

        parse_msg(plane, {2: lambda v: name.__setitem__(0, v.decode()),
                          3: lines.append, 4: on_emeta})
        if name[0] != "/device:TPU:0":
            continue
        tot = collections.Counter()
        cnt = collections.Counter()
        for line in lines:
            events = []
            parse_msg(line, {4: events.append})
            for ev in events:
                mid = [0]
                dur = [0]
                parse_msg(ev, {1: lambda x: mid.__setitem__(0, x),
                               3: lambda x: dur.__setitem__(0, x)})
                nm = emeta.get(mid[0], f"id{mid[0]}")
                tot[nm] += dur[0]
                cnt[nm] += 1
        print("== top TPU ops by total time")
        for nm, ps in tot.most_common(top):
            print(f"  {ps / 1e9:9.1f}ms x{cnt[nm]:5d}  {str(nm)[:110]}")


def main():
    task = sys.argv[1] if len(sys.argv) > 1 else "logistic"
    import jax.numpy as jnp
    import numpy as np

    from photon_tpu.obs.trace import profile_session
    from photon_tpu.utils import enable_compilation_cache

    enable_compilation_cache()
    import bench

    data = bench.build_data(task)
    est = bench.build_estimator(task)
    est.prepare(data)

    def fit():
        r = est.fit(data)[0]
        for m in r.model.models.values():
            c = (m.coefficients if hasattr(m, "coefficients")
                 else m.model.coefficients.means)
            float(np.asarray(jnp.sum(c)))

    fit()  # compile + load
    tracedir = tempfile.mkdtemp(prefix="jaxtrace")
    # THE profiling entry point (obs/trace.py): the captured xplane
    # profile is bracketed by an obs span + start/stop instants, so it
    # correlates with the exported host timeline.
    with profile_session(tracedir, name="trace_top_ops"):
        fit()
    paths = glob.glob(os.path.join(
        tracedir, "plugins/profile/*/*.xplane.pb"))
    top_ops(paths[0])
    shutil.rmtree(tracedir, ignore_errors=True)


if __name__ == "__main__":
    main()
