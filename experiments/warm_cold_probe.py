"""Measure the logistic variant's compile phase with the persistent cache
enabled, in this process. Run twice (two processes) to compare cold-ish vs
warm-cache behavior."""

import sys
import time

sys.path.insert(0, "/root/repo")

from photon_tpu.utils import enable_compilation_cache  # noqa: E402

print("cache dir:", enable_compilation_cache(), flush=True)

import numpy as np  # noqa: E402

import bench  # noqa: E402

t0 = time.perf_counter()
data = bench.build_data("logistic")
print(f"build_data {time.perf_counter() - t0:.1f}s", flush=True)
est = bench.build_estimator("logistic")
t0 = time.perf_counter()
datasets, _ = est.prepare(data)
print(f"prepare {time.perf_counter() - t0:.1f}s", flush=True)

t0 = time.perf_counter()
r = est.fit(data)[0]
for m in r.model.models.values():
    c = (m.coefficients if hasattr(m, "coefficients")
         else m.model.coefficients.means)
    float(np.asarray(c).sum())
print(f"first fit {time.perf_counter() - t0:.1f}s", flush=True)
t0 = time.perf_counter()
r = est.fit(data)[0]
for m in r.model.models.values():
    c = (m.coefficients if hasattr(m, "coefficients")
         else m.model.coefficients.means)
    float(np.asarray(c).sum())
print(f"second fit {time.perf_counter() - t0:.1f}s", flush=True)
