"""Probe: fused Newton-iteration pallas kernel vs XLA einsums at bench shapes.

Stage 1: just the z/H/g build (no CG) in one slab pass, flat [B, R*S] input.
"""
import time
from functools import partial

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

B, R, S = 99_976, 64, 17
BT = 8  # entities per kernel instance

rng = np.random.default_rng(0)
x_np = rng.normal(size=(B, R, S)).astype(np.float32)
x_flat = jnp.asarray(x_np.reshape(B, R * S))
x_brs = jnp.asarray(x_np)
w = jnp.asarray(rng.normal(size=(B, S)).astype(np.float32) * 0.1)
y = jnp.asarray((rng.random((B, R)) > 0.5).astype(np.float32))
wt = jnp.asarray(rng.random((B, R)).astype(np.float32))
off = jnp.zeros((B, R), jnp.float32)


def kernel(x_ref, w_ref, y_ref, wt_ref, off_ref, h_ref, g_ref):
    x = x_ref[...]
    wv = w_ref[...]
    # Batched dots don't lower in this pallas version; unroll the (static)
    # entity block with 2D dot_generals.
    for j in range(BT):
        xj = x[j]  # [R, S]
        z = (xj @ wv[j][:, None])[:, 0] + off_ref[j, :]
        p = jax.nn.sigmoid(z)
        c = wt_ref[j, :] * p * (1 - p)
        d1 = wt_ref[j, :] * (p - y_ref[j, :])
        h_ref[j, :, :] = xj.T @ (c[:, None] * xj)
        g_ref[j, :] = (xj.T @ d1[:, None])[:, 0]


@jax.jit
def fused(x3, w, y, wt, off):
    nb = B // BT
    return pl.pallas_call(
        kernel,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((BT, R, S), lambda i: (i, 0, 0)),
            pl.BlockSpec((BT, S), lambda i: (i, 0)),
            pl.BlockSpec((BT, R), lambda i: (i, 0)),
            pl.BlockSpec((BT, R), lambda i: (i, 0)),
            pl.BlockSpec((BT, R), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((BT, S, S), lambda i: (i, 0, 0)),
            pl.BlockSpec((BT, S), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, S, S), jnp.float32),
            jax.ShapeDtypeStruct((B, S), jnp.float32),
        ],
    )(x3, w, y, wt, off)


@jax.jit
def xla_version(x, w, y, wt, off):
    z = jnp.einsum("brs,bs->br", x, w) + off
    p = jax.nn.sigmoid(z)
    c = wt * p * (1 - p)
    d1 = wt * (p - y)
    h = jnp.einsum("brs,br,brt->bst", x, c, x)
    g = jnp.einsum("brs,br->bs", x, d1)
    return h, g


assert B % BT == 0 or True
Bpad = (B // BT) * BT  # truncate for the probe
xf, xb = x_flat[:Bpad], x_brs[:Bpad]
wv, yv, wtv, offv = w[:Bpad], y[:Bpad], wt[:Bpad], off[:Bpad]

h1, g1 = fused(xb, wv, yv, wtv, offv)
h2, g2 = xla_version(xb, wv, yv, wtv, offv)
print("parity h:", float(jnp.max(jnp.abs(h1 - h2))),
      "g:", float(jnp.max(jnp.abs(g1 - g2))))

for name, fn, args in (("pallas", fused, (xb, wv, yv, wtv, offv)),
                       ("xla", xla_version, (xb, wv, yv, wtv, offv))):
    float(np.asarray(fn(*args)[1]).sum())
    t0 = time.perf_counter()
    for _ in range(5):
        float(np.asarray(fn(*args)[1]).sum())
    print(f"{name}: {(time.perf_counter()-t0)/5*1000:.1f} ms per H/g build")
