"""Probe: ONE fused Newton step (H/g build -> CG -> line search -> new
objective) as a Pallas kernel, H never leaving VMEM, vs the XLA batched
step. Logistic loss, bench-user shapes.

Round-4 findings honored: no batched dots (unroll BT entities as 2D
dot_generals), operands kept 2D, 3D BlockSpecs, no reshapes across
tilings.
"""

import time

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

B, R, S = 99_976, 64, 17
BT = 8
T = 16  # line-search trials
TS = (0.5 ** np.arange(T)).astype(np.float32)


def _sigmoid(z):
    return 1.0 / (1.0 + jnp.exp(-z))


def kernel(x_ref, w_ref, y_ref, wt_ref, off_ref, l2_ref, mt_ref, vm_ref,
           f_ref, w_out, f_out, g_out, imp_out):
    # STRICT 2-D CONVENTION (Mosaic rejects 1-D length-S reductions with
    # "Offset change"): per-entity S-vectors are [S, 1] columns, the
    # line-search trial axis is a [1, T] row; every reduction is a full
    # or single-axis reduce of a 2-D operand.
    ts_row = jnp.exp2(-jax.lax.broadcasted_iota(
        jnp.int32, (1, T), 1).astype(jnp.float32))  # [1, T]
    eye = (
        jax.lax.broadcasted_iota(jnp.int32, (S, S), 0)
        == jax.lax.broadcasted_iota(jnp.int32, (S, S), 1)
    ).astype(jnp.float32)
    for j in range(BT):
        xj = x_ref[j]                     # [R, S]
        wj = w_ref[j][:, None]            # [S, 1]
        l2 = l2_ref[j][:, None]
        mt = mt_ref[j][:, None]
        vm = vm_ref[j][:, None]
        yj = y_ref[j][:, None]            # [R, 1]
        wtj = wt_ref[j][:, None]
        offj = off_ref[j][:, None]
        z = xj @ wj + offj                # [R, 1]
        p = _sigmoid(z)
        c = wtj * p * (1 - p)
        d1 = wtj * (p - yj)
        h = xj.T @ (c * xj) + (l2 + (1.0 - vm)) * eye
        g = (xj.T @ d1 + l2 * (wj - mt)) * vm  # [S, 1]

        b0 = -g

        def cg_step(_, st):
            xx, rr, pp, rs = st
            hp = h @ pp
            alpha = rs / jnp.maximum(jnp.sum(pp * hp), 1e-30)
            xx = xx + alpha * pp
            rr = rr - alpha * hp
            rs2 = jnp.sum(rr * rr)
            pp = rr + (rs2 / jnp.maximum(rs, 1e-30)) * pp
            return xx, rr, pp, rs2

        d, _, _, _ = lax.fori_loop(
            0, S, cg_step, (jnp.zeros_like(b0), b0, b0, jnp.sum(b0 * b0))
        )
        d = d * vm
        gd = jnp.sum(g * d)
        bad = gd >= 0.0
        d = jnp.where(bad, -g, d)
        gd = jnp.where(bad, -jnp.sum(g * g), gd)

        zd = xj @ d                        # [R, 1]
        f_prev = f_ref[j, 0]
        z_t = z + zd * ts_row              # [R, T]
        loss_t = jnp.log1p(jnp.exp(-jnp.abs(z_t))) + jnp.maximum(z_t, 0.0) \
            - z_t * yj
        data_t = jnp.sum(wtj * loss_t, axis=0, keepdims=True)  # [1, T]
        w_t = wj + d * ts_row              # [S, T]
        reg_t = 0.5 * jnp.sum(
            l2 * (w_t - mt) ** 2, axis=0, keepdims=True)
        f_t = data_t + reg_t               # [1, T]
        armijo = f_t <= f_prev + 1e-4 * ts_row * gd
        # First (largest) passing t == max over passing trials: ts is
        # strictly decreasing (argmax on bools doesn't lower).
        t_sel = jnp.max(jnp.where(armijo, ts_row, 0.0))
        any_ok = t_sel > 0.0
        f_sel = jnp.sum(jnp.where(ts_row == t_sel, f_t, 0.0))
        improved = jnp.logical_and(any_ok, f_sel < f_prev)
        w_new = jnp.where(improved, wj + t_sel * d, wj)  # [S, 1]

        # Fresh objective + gradient at w_new (slab still in VMEM).
        z2 = xj @ w_new + offj
        loss2 = jnp.log1p(jnp.exp(-jnp.abs(z2))) + jnp.maximum(z2, 0.0) \
            - z2 * yj
        f_new = jnp.sum(wtj * loss2) + 0.5 * jnp.sum(
            l2 * (w_new - mt) ** 2)
        p2 = _sigmoid(z2)
        g_new = (xj.T @ (wtj * (p2 - yj)) + l2 * (w_new - mt)) * vm

        w_out[j] = w_new[:, 0]
        f_out[j, :] = f_new[None]
        g_out[j] = g_new[:, 0]
        imp_out[j, :] = improved.astype(jnp.float32)[None]


@jax.jit
def pallas_step(x, w, y, wt, off, l2, mt, vm, f):
    nb = x.shape[0] // BT
    return pl.pallas_call(
        kernel,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((BT, R, S), lambda i: (i, 0, 0)),
            pl.BlockSpec((BT, S), lambda i: (i, 0)),
            pl.BlockSpec((BT, R), lambda i: (i, 0)),
            pl.BlockSpec((BT, R), lambda i: (i, 0)),
            pl.BlockSpec((BT, R), lambda i: (i, 0)),
            pl.BlockSpec((BT, S), lambda i: (i, 0)),
            pl.BlockSpec((BT, S), lambda i: (i, 0)),
            pl.BlockSpec((BT, S), lambda i: (i, 0)),
            pl.BlockSpec((BT, 1), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((BT, S), lambda i: (i, 0)),
            pl.BlockSpec((BT, 1), lambda i: (i, 0)),
            pl.BlockSpec((BT, S), lambda i: (i, 0)),
            pl.BlockSpec((BT, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((x.shape[0], S), jnp.float32),
            jax.ShapeDtypeStruct((x.shape[0], 1), jnp.float32),
            jax.ShapeDtypeStruct((x.shape[0], S), jnp.float32),
            jax.ShapeDtypeStruct((x.shape[0], 1), jnp.float32),
        ],
    )(x, w, y, wt, off, l2, mt, vm, f)


@jax.jit
def xla_step(x, w, y, wt, off, l2, mt, vm, f):
    """The batch-minor XLA step (mirrors _solve_newton_batched's body)."""
    f = f[:, 0]
    z = jnp.einsum("brs,bs->br", x, w) + off
    p = jax.nn.sigmoid(z)
    c = wt * p * (1 - p)
    h = jnp.einsum("brs,brt->bst", x * c[:, :, None], x)
    h = h + (l2 + (1.0 - vm))[:, :, None] * jnp.eye(S)[None]
    g = (jnp.einsum("brs,br->bs", x, wt * (p - y)) + l2 * (w - mt)) * vm
    h_sb = jnp.transpose(h, (1, 2, 0))

    def cg_step(_, st):
        xx, rr, pp, rs = st
        hp = jnp.sum(h_sb * pp[None, :, :], axis=1)
        denom = jnp.sum(pp * hp, axis=0)
        alpha = rs / jnp.maximum(denom, 1e-30)
        xx = xx + alpha[None] * pp
        rr = rr - alpha[None] * hp
        rs2 = jnp.sum(rr * rr, axis=0)
        pp = rr + (rs2 / jnp.maximum(rs, 1e-30))[None] * pp
        return xx, rr, pp, rs2

    b0 = -jnp.transpose(g)
    d0, _, _, _ = lax.fori_loop(
        0, S, cg_step,
        (jnp.zeros_like(b0), b0, b0, jnp.sum(b0 * b0, axis=0)))
    d = jnp.transpose(d0) * vm
    gd = jnp.sum(g * d, axis=-1)
    bad = gd >= 0.0
    d = jnp.where(bad[:, None], -g, d)
    gd = jnp.where(bad, -jnp.sum(g * g, axis=-1), gd)
    zd = jnp.einsum("brs,bs->br", x, d)
    ts = jnp.asarray(TS)
    z_t = z[None] + ts[:, None, None] * zd[None]
    loss_t = jnp.logaddexp(0.0, z_t) - z_t * y[None]
    w_t = w[None] + ts[:, None, None] * d[None]
    f_t = jnp.sum(wt[None] * loss_t, axis=-1) + 0.5 * jnp.sum(
        l2[None] * (w_t - mt[None]) ** 2, axis=-1)
    armijo = f_t <= f[None] + 1e-4 * ts[:, None] * gd[None]
    first = jnp.argmax(armijo, axis=0)
    any_ok = jnp.any(armijo, axis=0)
    t_sel = ts[first]
    f_sel = jnp.take_along_axis(f_t, first[None], axis=0)[0]
    improved = any_ok & (f_sel < f)
    w_new = jnp.where(improved[:, None], w + t_sel[:, None] * d, w)
    z2 = jnp.einsum("brs,bs->br", x, w_new) + off
    f_new = jnp.sum(wt * (jnp.logaddexp(0.0, z2) - z2 * y), axis=-1) \
        + 0.5 * jnp.sum(l2 * (w_new - mt) ** 2, axis=-1)
    p2 = jax.nn.sigmoid(z2)
    g_new = (jnp.einsum("brs,br->bs", x, wt * (p2 - y))
             + l2 * (w_new - mt)) * vm
    return w_new, f_new[:, None], g_new, improved.astype(jnp.float32)[:, None]


def main():
    rng = np.random.default_rng(0)
    bpad = (B // BT) * BT
    x = jnp.asarray(rng.normal(size=(bpad, R, S)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(bpad, S)).astype(np.float32) * 0.1)
    y = jnp.asarray((rng.random((bpad, R)) > 0.5).astype(np.float32))
    wt = jnp.asarray(rng.random((bpad, R)).astype(np.float32))
    off = jnp.zeros((bpad, R), jnp.float32)
    l2 = jnp.ones((bpad, S), jnp.float32)
    mt = jnp.zeros((bpad, S), jnp.float32)
    vm = jnp.ones((bpad, S), jnp.float32)
    # consistent starting objective values
    z = jnp.einsum("brs,bs->br", x, w)
    f0 = jnp.sum(wt * (jnp.logaddexp(0.0, z) - z * y), axis=-1) \
        + 0.5 * jnp.sum(l2 * w ** 2, axis=-1)
    f = f0[:, None]

    args = (x, w, y, wt, off, l2, mt, vm, f)
    t0 = time.perf_counter()
    outs_p = pallas_step(*args)
    print(f"pallas compile+run: {time.perf_counter() - t0:.1f}s",
          flush=True)
    outs_x = xla_step(*args)
    for a, b, name in zip(outs_p, outs_x, ("w", "f", "g", "imp")):
        err = float(jnp.max(jnp.abs(a - b)))
        rel = err / (float(jnp.max(jnp.abs(b))) + 1e-30)
        print(f"parity {name}: max abs {err:.3e} rel {rel:.3e}",
              flush=True)

    for name, fn in (("pallas", pallas_step), ("xla", xla_step)):
        float(np.asarray(fn(*args)[1]).sum())
        t0 = time.perf_counter()
        for _ in range(5):
            float(np.asarray(fn(*args)[1]).sum())
        print(f"{name}: {(time.perf_counter() - t0) / 5 * 1000:.1f} ms "
              "per Newton step", flush=True)


if __name__ == "__main__":
    main()
